"""KV economy report: reuse heatmap, fleet duplication table, fragmentation.

Reads a ``/debug/kv`` payload (URL, file path, or ``-`` for stdin) from
EITHER surface — the gateway's fleet view (``gateway/kvobs.py``: per-pod
rows + duplication index) or a single model server's ledger snapshot
(``server/kv_ledger.py``: block states + prefix table + histograms) — or
the ``kv`` section of a black-box dump, and renders the operator view:

- the per-pod economy table (KV usage, parked share, reuse efficiency,
  cache-savings rate);
- the prefix reuse heatmap (hottest prefixes fleet-wide, which replicas
  hold them);
- the duplication table ("prefix P resident on k replicas, N blocks
  duplicated, M tokens/s servable by one shared copy");
- a fragmentation/headroom summary from a server ledger's free-run and
  parked-share histograms.

``--baseline`` regenerates the committed ``KV_BASELINE.json`` evidence
artifact: a deterministic 4-replica SimServer fleet serving a shared
system prompt (every replica caches the same prefix — >=3x duplication),
rolled up through the REAL gateway join (``KvObsRollup``), no RNG and no
wall clock, so CI re-derives the identical document byte-for-byte.

Usage:
  python tools/kv_report.py http://localhost:9002/debug/kv        # watch
  python tools/kv_report.py http://localhost:9002/debug/kv --once
  python tools/kv_report.py KV_BASELINE.json
  python tools/kv_report.py --baseline --artifact KV_BASELINE.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.trace_report import load  # noqa: E402 — one loader, no drift

BASELINE_FORMAT = "lig-kv-baseline/1"


# ---------------------------------------------------------------------------
# Payload extraction
# ---------------------------------------------------------------------------


def extract_kv(doc: dict) -> tuple[str, dict]:
    """Classify a payload: ``("gateway", payload)`` for the fleet rollup
    shape, ``("server", payload)`` for one ledger snapshot.  Accepts the
    baseline artifact (``kv`` section) and a black-box dump (``kv`` ->
    ``gateway``/``pods``)."""
    if not isinstance(doc, dict):
        raise ValueError("payload is not a JSON object")
    if isinstance(doc.get("kv"), dict):
        inner = doc["kv"]
        # Black-box dump shape: {"gateway": rollup, "pods": {name: raw}}.
        if isinstance(inner.get("gateway"), dict):
            return "gateway", inner["gateway"]
        return extract_kv(inner)
    if "duplication" in doc and "pods" in doc:
        return "gateway", doc
    if "states" in doc and "blocks_total" in doc:
        return "server", doc
    raise ValueError("no KV payload found (expected a gateway /debug/kv "
                     "body, a server ledger snapshot, or a dump's 'kv' "
                     "section)")


# ---------------------------------------------------------------------------
# Rows (pure — the testable core)
# ---------------------------------------------------------------------------


def pod_rows(gw: dict) -> list[dict]:
    rows = []
    for name, view in sorted((gw.get("pods") or {}).items()):
        rows.append({
            "pod": name,
            "blocks": view.get("blocks_total", 0),
            "usage_pct": round(100.0 * view.get("usage", 0.0), 1),
            "parked_pct": round(100.0 * view.get("parked_share", 0.0), 1),
            "reuse_eff_pct": round(
                100.0 * view.get("reuse_efficiency", 0.0), 1),
            "saved_tok_s": view.get("saved_tokens_per_s", 0.0),
        })
    return rows


def heatmap_rows(gw: dict, top: int = 16) -> list[dict]:
    """Hottest prefixes fleet-wide: fleet hits/savings summed across the
    pods' per-prefix tables, holders listed as ``pod:blocks``."""
    agg: dict[str, dict] = {}
    for pod, view in sorted((gw.get("pods") or {}).items()):
        for prefix, e in (view.get("prefixes") or {}).items():
            row = agg.setdefault(prefix, {"prefix": prefix, "hits": 0,
                                          "tokens_saved": 0, "holders": []})
            row["hits"] += int(e.get("hits", 0))
            row["tokens_saved"] += int(e.get("tokens_saved", 0))
            if e.get("blocks"):
                row["holders"].append(f"{pod}:{e['blocks']}")
    rows = sorted(agg.values(),
                  key=lambda r: (-r["hits"], -r["tokens_saved"],
                                 r["prefix"]))[:top]
    for r in rows:
        r["replicas"] = len(r["holders"])
        r["holders"] = " ".join(r["holders"]) or "-"
    return rows


def duplication_rows(gw: dict) -> list[dict]:
    rows = []
    for r in ((gw.get("duplication") or {}).get("prefixes") or []):
        rows.append({
            "prefix": r.get("prefix", "?"),
            "replicas": r.get("replicas", 0),
            "dup_blocks": r.get("duplicated_blocks", 0),
            "dup_tokens": r.get("duplicated_tokens", 0),
            "dedup_tok_s": r.get("dedup_tokens_saved_per_s", 0.0),
        })
    return rows


def fragmentation_summary(ledger: dict) -> dict:
    """Headroom shape from one server ledger snapshot: states, the mean
    and max free-run length (can a growth burst find room?), parked
    share samples."""
    runs = ledger.get("free_runs") or {}
    counts = runs.get("counts") or []
    buckets = runs.get("buckets") or []
    n = int(runs.get("count", 0))
    max_bucket = 0.0
    for i, c in enumerate(counts):
        if c:
            max_bucket = (buckets[i] if i < len(buckets)
                          else float("inf"))
    return {
        "states": dict(ledger.get("states") or {}),
        "blocks_total": ledger.get("blocks_total", 0),
        "parked_tokens": ledger.get("parked_tokens", 0),
        "free_runs": n,
        "mean_run_blocks": round(runs.get("sum", 0.0) / n, 2) if n else 0.0,
        "max_run_bucket": max_bucket,
        "prefix_table_size": ledger.get("prefix_table_size", 0),
        "prefix_table_evictions": ledger.get("prefix_table_evictions", 0),
    }


def _table(rows: list[dict], headers: tuple) -> str:
    if not rows:
        return "(no samples)"
    widths = [max(len(h), *(len(str(r[h])) for r in rows)) for h in headers]

    def fmt(vals):
        return "  ".join(str(v).rjust(w) if i else str(v).ljust(w)
                         for i, (v, w) in enumerate(zip(vals, widths)))

    lines = [fmt(headers), fmt(["-" * w for w in widths])]
    lines += [fmt([r[h] for h in headers]) for r in rows]
    return "\n".join(lines)


def render_gateway(gw: dict) -> str:
    dup = gw.get("duplication") or {}
    out = [
        "FLEET KV ECONOMY "
        f"(ticks={gw.get('ticks', 0)}, pods={len(gw.get('pods') or {})})",
        "",
        _table(pod_rows(gw), ("pod", "blocks", "usage_pct", "parked_pct",
                              "reuse_eff_pct", "saved_tok_s")),
        "",
        "Prefix reuse heatmap (fleet-wide, hottest first):",
        _table(heatmap_rows(gw), ("prefix", "replicas", "hits",
                                  "tokens_saved", "holders")),
        "",
        f"Duplication index: {dup.get('duplicated_prefixes', 0)} prefixes "
        f"on >=2 replicas, {dup.get('duplicated_blocks', 0)} blocks "
        f"({dup.get('duplicated_tokens', 0)} tokens) duplicated, "
        f"{dup.get('dedup_tokens_saved_per_s', 0.0)} tok/s servable by a "
        "shared copy:",
        _table(duplication_rows(gw), ("prefix", "replicas", "dup_blocks",
                                      "dup_tokens", "dedup_tok_s")),
    ]
    return "\n".join(out)


def render_server(ledger: dict) -> str:
    frag = fragmentation_summary(ledger)
    states = frag["states"]
    state_rows = [{"state": s, "blocks": states.get(s, 0)}
                  for s in ("free", "active", "prefix_resident", "parked")]
    prefix_rows = [
        {"prefix": e.get("prefix", "?"), "hits": e.get("hits", 0),
         "tokens_saved": e.get("tokens_saved", 0),
         "blocks": e.get("blocks", 0), "age_s": e.get("age_s", 0.0)}
        for e in (ledger.get("prefixes") or [])[:16]]
    out = [
        "SERVER KV LEDGER "
        f"(blocks_total={frag['blocks_total']}, "
        f"block_tokens={ledger.get('block_tokens', 0)}, "
        f"syncs={ledger.get('syncs', 0)})",
        "",
        _table(state_rows, ("state", "blocks")),
        "",
        "Prefix reuse heatmap (hottest first):",
        _table(prefix_rows, ("prefix", "hits", "tokens_saved", "blocks",
                             "age_s")),
        "",
        "Fragmentation/headroom: "
        f"{frag['free_runs']} free runs, mean {frag['mean_run_blocks']} "
        f"blocks, longest-run bucket <= {frag['max_run_bucket']}; "
        f"parked {frag['parked_tokens']} tokens; prefix table "
        f"{frag['prefix_table_size']} entries "
        f"({frag['prefix_table_evictions']} evicted)",
    ]
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Deterministic baseline scenario (the committed KV_BASELINE.json)
# ---------------------------------------------------------------------------


def run_baseline() -> dict:
    """Four sim replicas behind one gateway rollup, all serving the same
    shared-prefix template (plus a 2-replica template and per-pod unique
    prefixes) — deterministic: fixed request plan, stepped sim clock, no
    RNG, no wall time."""
    from llm_instance_gateway_tpu.gateway import kvobs
    from llm_instance_gateway_tpu.sim.core import (
        SimRequest, SimServer, V5E_DEFAULT)

    shared_prefix, pair_prefix = 0xA11CE, 0xB0B
    servers = [SimServer(f"sim-{i}", V5E_DEFAULT, decode_slots=8,
                         kv_capacity_tokens=8192, kv_block_tokens=16)
               for i in range(4)]
    rid = 0
    for i, srv in enumerate(servers):
        plan = [(shared_prefix, 256)] * 3 + [(0x100 + i, 64)]
        if i < 2:
            plan += [(pair_prefix, 128)] * 2
        t = 0.0
        for prefix_id, prefix_tokens in plan:
            rid += 1
            srv.prefill_queue.append(SimRequest(
                rid=rid, arrival_s=t, prompt_tokens=prefix_tokens + 32,
                output_tokens=4, model="sim", prefix_id=prefix_id,
                prefix_tokens=prefix_tokens))
            # Drain the admission: step until the queue empties (each
            # iteration admits at most one request, engine-loop shape).
            for _ in range(8):
                t += srv.step(t) or 0.05
                if not srv.prefill_queue:
                    break

    class _Provider:
        def __init__(self, fleet):
            self.fleet = fleet

        def all_pod_metrics(self):
            return [s.metrics() for s in self.fleet]

    t = [0.0]
    rollup = kvobs.KvObsRollup(_Provider(servers), clock=lambda: t[0])
    rollup.tick()
    t[0] = 10.0
    rollup.tick()
    payload = rollup.debug_payload()
    dup = payload["duplication"]
    top = dup["prefixes"][0] if dup["prefixes"] else {}
    return {
        "format": BASELINE_FORMAT,
        "scenario": {
            "replicas": len(servers),
            "shared_prefix": "%016x" % shared_prefix,
            "plan": "3x shared(256tok) on all pods, 2x pair(128tok) on "
                    "pods 0-1, 1 unique(64tok) per pod",
        },
        # Max copies of one prefix beyond the first — the headline ">=3x
        # duplicated" number the acceptance gate pins.
        "duplication_factor": max(
            [r["replicas"] - 1 for r in dup["prefixes"]] or [0]),
        "kv": payload,
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def render(doc: dict) -> str:
    kind, payload = extract_kv(doc)
    text = (render_gateway(payload) if kind == "gateway"
            else render_server(payload))
    if doc.get("format") == BASELINE_FORMAT:
        text = (f"(baseline artifact, duplication_factor="
                f"{doc.get('duplication_factor')})\n\n") + text
    return text


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="KV economy report: reuse heatmap, duplication index, "
                    "fragmentation (from /debug/kv)")
    parser.add_argument("source", nargs="?",
                        help="file path, http(s) URL, or - for stdin")
    parser.add_argument("--once", action="store_true",
                        help="render one report and exit (CI mode; URL "
                             "sources otherwise refresh every --interval)")
    parser.add_argument("--interval", type=float, default=5.0,
                        help="watch-mode refresh seconds (URL sources)")
    parser.add_argument("--json", action="store_true",
                        help="emit the extracted rows as JSON")
    parser.add_argument("--baseline", action="store_true",
                        help="regenerate the deterministic 4-replica "
                             "shared-prefix scenario (KV_BASELINE.json)")
    parser.add_argument("--artifact",
                        help="write the payload (baseline mode) or rows "
                             "(--json) to this path instead of stdout")
    args = parser.parse_args(argv)

    if args.baseline:
        payload = run_baseline()
        text = json.dumps(payload, indent=1, sort_keys=True)
        if args.artifact:
            with open(args.artifact, "w", encoding="utf-8") as f:
                f.write(text + "\n")
            print(f"wrote {args.artifact} (duplication_factor="
                  f"{payload['duplication_factor']})")
        else:
            print(text)
        return 0
    if not args.source:
        parser.error("a source is required unless --baseline is given")

    watch = (not args.once and not args.json
             and args.source.startswith(("http://", "https://")))
    while True:
        try:
            doc = load(args.source)
            kind, payload = extract_kv(doc)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.json:
            rows = ({"kind": kind, "pods": pod_rows(payload),
                     "heatmap": heatmap_rows(payload),
                     "duplication": duplication_rows(payload)}
                    if kind == "gateway"
                    else {"kind": kind,
                          "fragmentation": fragmentation_summary(payload),
                          "prefixes": payload.get("prefixes") or []})
            text = json.dumps(rows, indent=1)
            if args.artifact:
                with open(args.artifact, "w", encoding="utf-8") as f:
                    f.write(text + "\n")
            else:
                print(text)
            return 0
        if watch:
            print("\x1b[2J\x1b[H", end="")
        print(render(doc))
        if not watch:
            return 0
        time.sleep(max(0.5, args.interval))


if __name__ == "__main__":
    sys.exit(main())
