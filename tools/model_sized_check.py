"""Model-sized virtual-mesh serving check (VERDICT r4 #9).

Every other multi-device certification in this repo runs on toy shapes
(d_model 64-128) — right for correctness, silent on the question "does the
sharding/memory plumbing hold up at model scale?".  This check serves a
~1.14B-parameter config with the REAL Llama-3 head layout (GQA, 8 KV
heads, head_dim 128 — the layout `models/configs.LLAMA3_8B` declares,
scaled to 1B the way the Llama-3.2-1B family is) over a tensor=8 virtual
CPU mesh: params shard Megatron-style, the decode cache shards its KV
heads, and a few greedy tokens decode end to end through the full engine
(bucketed prefill -> insert -> fused decode).  `--int8` additionally runs
the quantized cache + quant-aware shard_map wrapper at the same scale.

This exercises, at real-model tensor sizes, exactly what first contact
with a v5e-8 would: GSPMD spec/shape agreement on multi-GB params, scale
pools, LoRA-free fast paths, and the engine's committed-input sharding.
It does NOT measure speed (1-host CPU emulates 8 devices) and is gated
behind an env var because init+compile+prefill of a 1B model on one CPU
core takes minutes:

    LIG_MODEL_SIZED=1 python tools/model_sized_check.py [--int8]

or via the (slow, opt-in) test: LIG_MODEL_SIZED=1 pytest
tests/test_parallel.py -k model_sized.  A recorded run lives in
ARCHITECTURE.md §4.

Reference note: the reference gateway never touches model tensors (it
delegates serving to vLLM, SURVEY §2); this check belongs to the
model-server half this repo owns (SURVEY §2.5 "slice-backed replica").
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_DEVICES = 8


def _ensure_cpu_mesh() -> None:
    """Pin CPU + 8 virtual devices, re-execing if a backend already exists
    (same approach as __graft_entry__.dryrun_multichip)."""
    import re
    import subprocess

    if os.environ.get("_LIG_MODEL_SIZED_CHILD") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
        return
    # Cheap path: if this interpreter can already see enough CPU devices
    # (e.g. XLA_FLAGS was set by the caller / conftest), skip the re-exec.
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        if jax.device_count() >= N_DEVICES:
            return
    except RuntimeError:
        pass  # backend already initialized differently: re-exec below
    inherited = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        os.environ.get("XLA_FLAGS", ""),
    ).strip()
    env = dict(
        os.environ,
        XLA_FLAGS=(
            f"{inherited} --xla_force_host_platform_device_count={N_DEVICES}"
        ).strip(),
        _LIG_MODEL_SIZED_CHILD="1",
    )
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run([sys.executable, os.path.abspath(__file__),
                           *sys.argv[1:]], env=env, timeout=3600)
    raise SystemExit(proc.returncode)


def model_sized_config():
    """~1.14B params, Llama-3.2-1B-like: GQA 16q/8kv heads x 128."""
    from llm_instance_gateway_tpu.models.configs import LLAMA3_8B

    return dataclasses.replace(
        LLAMA3_8B,
        name="llama3-1b-meshcheck",
        vocab_size=32768,
        d_model=2048,
        n_layers=16,
        n_heads=16,
        n_kv_heads=8,
        head_dim=128,
        d_ff=8192,
        max_seq_len=512,
    )


def run(int8: bool = False, max_new: int = 4) -> dict:
    """Serve a few greedy tokens at 1B scale on a tensor=8 virtual mesh.
    Returns a result dict (also printed as the one-line summary)."""
    import jax
    import jax.numpy as jnp

    from llm_instance_gateway_tpu.models import transformer
    from llm_instance_gateway_tpu.parallel.mesh import MeshConfig, make_mesh
    from llm_instance_gateway_tpu.server.engine import (
        Engine, EngineConfig, Request, SamplingParams,
    )

    cfg = model_sized_config()
    t0 = time.monotonic()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.bfloat16)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    t_init = time.monotonic() - t0

    devices = jax.devices("cpu")[:N_DEVICES]
    mesh = make_mesh(MeshConfig(tensor=N_DEVICES), devices=devices)
    engine = Engine(
        cfg, params,
        EngineConfig(decode_slots=4, max_seq_len=256, prefill_buckets=(64,),
                     kv_cache_quant="int8" if int8 else None),
        eos_id=None, dtype=jnp.bfloat16, mesh=mesh,
    )
    quant_aware = bool(getattr(engine._decode_attn_fn, "quant_aware", False))
    t1 = time.monotonic()
    engine.start()
    try:
        reqs = [Request(prompt_tokens=[5 + i, 6, 7], max_new_tokens=max_new,
                        sampling=SamplingParams(temperature=0.0))
                for i in range(2)]
        for r in reqs:
            engine.submit(r)
        for r in reqs:
            if not r.done.wait(3000):
                raise RuntimeError("model-sized decode timed out")
            if r.error:
                raise RuntimeError(f"model-sized decode failed: {r.error}")
        served = [len(r.output_tokens) for r in reqs]
    finally:
        engine.stop()
    t_serve = time.monotonic() - t1

    result = {
        "params": n_params,
        "mesh": dict(mesh.shape),
        "int8": int8,
        "quant_kernel_wrapper": quant_aware,
        "served_tokens": served,
        "init_s": round(t_init, 1),
        "serve_s": round(t_serve, 1),
    }
    print(f"model_sized_check OK: params={n_params/1e9:.2f}B "
          f"mesh={dict(mesh.shape)} int8={int8} "
          f"quant_kernel_wrapper={quant_aware} served={served} "
          f"init={t_init:.0f}s serve(compile+decode)={t_serve:.0f}s")
    return result


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--int8", action="store_true",
                        help="quantized KV cache + quant-aware wrapper")
    parser.add_argument("--max-new", type=int, default=4)
    args = parser.parse_args(argv)
    if not os.environ.get("LIG_MODEL_SIZED"):
        print("set LIG_MODEL_SIZED=1 to run (minutes of CPU compile)")
        raise SystemExit(2)
    _ensure_cpu_mesh()
    run(int8=args.int8, max_new=args.max_new)


if __name__ == "__main__":
    main()
