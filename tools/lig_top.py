#!/usr/bin/env python
"""lig-top: a top(1)-style live console over the gateway's /debug/usage.

Answers "who is consuming the pool RIGHT NOW" from the capacity-attribution
plane (gateway/usage.py over the replicas' tpu:adapter_*_total families):
one row per {model, adapter} with its consumption shares (TPU step-seconds,
tokens, KV block-seconds), admitted-traffic share, noisy-neighbor score,
and flag state — plus the pool-waste line (idle slot-seconds, prefill
padding) nobody previously saw.

Usage:
    python tools/lig_top.py --url http://localhost:8081            # live
    python tools/lig_top.py --url http://localhost:8081 --once     # CI logs
    make top                                                       # one-shot

``--once`` renders a single frame to stdout (no ANSI) so CI jobs and
post-mortems can embed the table; live mode redraws every ``--interval``
seconds until Ctrl-C.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request

CLEAR = "\x1b[2J\x1b[H"
BOLD, RED, DIM, RESET = "\x1b[1m", "\x1b[31m", "\x1b[2m", "\x1b[0m"

COLUMNS = ("MODEL", "ADAPTER", "STEP%", "TOK%", "KV%", "TRAF%", "SCORE",
           "STATE", "TIERS", "STEER", "HEADROOM")
WIDTHS = (18, 18, 7, 7, 7, 7, 7, 7, 14, 6, 8)


def fetch_usage(url: str, timeout_s: float = 5.0) -> dict:
    with urllib.request.urlopen(
            url.rstrip("/") + "/debug/usage", timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def fetch_kv(url: str, timeout_s: float = 5.0) -> dict | None:
    """Best-effort /debug/kv fetch (gateway/kvobs.py) — the KV economy
    section degrades to absent against gateways predating the ledger."""
    try:
        with urllib.request.urlopen(
                url.rstrip("/") + "/debug/kv", timeout=timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except (OSError, ValueError):
        return None


def fetch_picks(url: str, timeout_s: float = 5.0) -> dict | None:
    """Best-effort /debug/picks fetch (gateway/pickledger.py) — the
    steering column degrades to '-' against gateways predating the
    decision ledger."""
    try:
        with urllib.request.urlopen(
                url.rstrip("/") + "/debug/picks?limit=256",
                timeout=timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except (OSError, ValueError):
        return None


def fetch_capacity(url: str, timeout_s: float = 5.0) -> dict | None:
    """Best-effort /debug/capacity fetch (gateway/capacity.py) — the
    HEADROOM column degrades to '-' against gateways predating the
    capacity plane."""
    try:
        with urllib.request.urlopen(
                url.rstrip("/") + "/debug/capacity",
                timeout=timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except (OSError, ValueError):
        return None


def headroom_cell(capacity: dict | None) -> str:
    """The HEADROOM column value — pool headroom-at-SLO from the capacity
    plane (one pool per gateway, so every tenant row shares it); '?'
    suffix when the twin is drifted/uncalibrated and the number is
    exported-but-untrusted."""
    if not capacity:
        return "-"
    fc = capacity.get("forecast") or {}
    cell = "%.0f%%" % (100.0 * fc.get("headroom_ratio", 0.0))
    return cell if fc.get("trusted") else cell + "?"


def capacity_lines(capacity: dict | None) -> list[str]:
    """The capacity/forecast summary line (pure; from /debug/capacity):
    pool saturation indices, offered vs knee, time-to-breach, twin
    trust."""
    if not capacity:
        return []
    fc = capacity.get("forecast") or {}
    sat = capacity.get("saturation") or {}
    ttb = fc.get("time_to_breach_s", -1.0)
    return [
        "capacity: offered=%.1frps knee=%.1frps headroom=%s ttb=%s "
        "sat={%s} twin=%s%s"
        % (fc.get("offered_rps", 0.0), fc.get("knee_rps", 0.0),
           headroom_cell(capacity),
           "none" if ttb is None or ttb < 0 else "%.0fs" % ttb,
           ", ".join(f"{k}:{sat[k]:.2f}" for k in sorted(sat)),
           (capacity.get("twin") or {}).get("state", "?"),
           " BREACH-ALARM" if fc.get("breach_alarm") else "")]


def steer_counts(picks: dict | None) -> dict[tuple[str, str], int]:
    """Per-{model, adapter} steered-pick counts over the recent sampled
    decision records (pure; feeds the STEER column)."""
    counts: dict[tuple[str, str], int] = {}
    for r in (picks or {}).get("records") or []:
        if r.get("steered"):
            key = (r.get("model", ""), r.get("adapter", ""))
            counts[key] = counts.get(key, 0) + 1
    return counts


def pick_lines(picks: dict | None) -> list[str]:
    """The routing-decision summary line (pure; from /debug/picks):
    sample coverage, per-seam steering counts, and the decisive-seam
    distribution across sampled picks."""
    if not picks:
        return []
    steered = picks.get("rollup", {}).get("steered") or {}
    decisive = picks.get("decisive") or {}
    return [
        "picks: sampled=%d/%d steered={%s} decisive={%s}"
        % (picks.get("samples", 0), picks.get("picks", 0),
           ", ".join(f"{k}:{steered[k]}" for k in sorted(steered)) or "none",
           ", ".join(f"{k}:{decisive[k]}" for k in sorted(decisive))
           or "none")]


def _row(values, color: str = "") -> str:
    cells = []
    for v, w in zip(values, WIDTHS):
        s = str(v)
        if len(s) > w:
            s = s[: w - 1] + "…"
        cells.append(s.ljust(w))
    line = " ".join(cells).rstrip()
    return f"{color}{line}{RESET}" if color else line


def kv_lines(kv: dict | None) -> list[str]:
    """The KV economy section (pure; from the gateway's /debug/kv): one
    line per pod (usage, parked share, reuse efficiency) plus the fleet
    duplication headline with the top duplicated prefix."""
    if not kv:
        return []
    lines = []
    for name, view in sorted((kv.get("pods") or {}).items()):
        lines.append(
            "kv %-12s usage=%.1f%% parked=%.1f%% reuse_eff=%.1f%% "
            "saved=%.1ftok/s"
            % (name, 100 * view.get("usage", 0.0),
               100 * view.get("parked_share", 0.0),
               100 * view.get("reuse_efficiency", 0.0),
               view.get("saved_tokens_per_s", 0.0)))
    dup = kv.get("duplication") or {}
    top = (dup.get("prefixes") or [{}])[0]
    lines.append(
        "kv duplication: %d prefixes / %d blocks on >=2 replicas%s"
        % (dup.get("duplicated_prefixes", 0),
           dup.get("duplicated_blocks", 0),
           ("; top %s x%d" % (top.get("prefix", "?"),
                              top.get("replicas", 0))
            if top.get("prefix") else "")))
    return lines


def render_table(payload: dict, color: bool = False,
                 kv: dict | None = None,
                 picks: dict | None = None,
                 capacity: dict | None = None) -> str:
    """One frame of the console (pure function — unit-tested and shared by
    --once).  Rows arrive pre-sorted by step-seconds share, descending."""
    lines = []
    waste = payload.get("pool_waste") or {}
    noisy = payload.get("noisy") or []
    header = ("lig-top — pool capacity attribution  "
              f"(ticks={payload.get('ticks', 0)})")
    lines.append(f"{BOLD}{header}{RESET}" if color else header)
    lines.append(
        "pool waste: idle_slot_seconds=%.1f prefill_padding_tokens=%d"
        % (waste.get("idle_slot_seconds", 0.0),
           waste.get("prefill_padding_tokens", 0)))
    lines.append("noisy: %s" % (", ".join(noisy) if noisy else "none"))
    # Residency ladder summary (placement plane): where each tenant's
    # weights live, next to what they cost.  pod -> {adapter: tier}.
    residency = payload.get("residency") or {}
    tier_counts: dict[str, dict[str, int]] = {}
    for tiers in residency.values():
        for adapter, tier in tiers.items():
            per = tier_counts.setdefault(adapter, {})
            per[tier] = per.get(tier, 0) + 1
    if residency:
        slot_total = sum(per.get("slot", 0) for per in tier_counts.values())
        host_total = sum(per.get("host", 0) for per in tier_counts.values())
        lines.append("residency: %d slot / %d host copies across %d pods"
                     % (slot_total, host_total, len(residency)))
    lines += kv_lines(kv)
    lines += capacity_lines(capacity)
    lines += pick_lines(picks)
    fairness = payload.get("fairness") or {}
    if fairness:
        lines.append(
            "fairness: mode=%s throttles=%d demotions=%d escapes=%d"
            % (fairness.get("mode", "log_only"),
               fairness.get("quota_throttles_total", 0),
               fairness.get("fairness_demotions_total", 0),
               fairness.get("escape_total", 0)))
        for row in fairness.get("throttled") or []:
            line = ("  throttled %s/%s share=%.2f fair=%.2f quota=%.1f "
                    "demotions=%d"
                    % (row.get("model", ""), row.get("adapter", ""),
                       row.get("share", 0.0), row.get("fair_share", 0.0),
                       row.get("quota_remaining", 0.0),
                       row.get("demotions", 0)))
            lines.append(f"{RED}{line}{RESET}" if color else line)
    lines.append("")
    head = _row(COLUMNS, BOLD if color else "")
    lines.append(head)
    rows = payload.get("adapters") or []
    if not rows:
        lines.append("(no attribution samples yet — is traffic flowing "
                     "and are replicas exposing tpu:adapter_*_total?)")
    steers = steer_counts(picks)
    hr_cell = headroom_cell(capacity)
    for r in rows:
        share = r.get("share") or {}
        flagged = r.get("state") == "noisy"
        per = tier_counts.get(r.get("adapter", ""), {})
        tiers_cell = ",".join(f"{t}x{per[t]}" for t in ("slot", "host")
                              if per.get(t)) or ("-" if residency else "")
        steer_cell = ("-" if picks is None else
                      str(steers.get((r.get("model", ""),
                                      r.get("adapter", "")), 0)))
        lines.append(_row((
            r.get("model", ""), r.get("adapter", ""),
            "%.1f" % (100 * share.get("step_seconds", 0.0)),
            "%.1f" % (100 * share.get("tokens", 0.0)),
            "%.1f" % (100 * share.get("kv_block_seconds", 0.0)),
            "%.1f" % (100 * r.get("traffic_share", 0.0)),
            "%.2f" % r.get("score", 0.0),
            r.get("state", "quiet"),
            tiers_cell,
            steer_cell,
            hr_cell,
        ), RED if (flagged and color) else ""))
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--url", default="http://localhost:8081",
                        help="gateway base URL (default %(default)s)")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh seconds in live mode")
    parser.add_argument("--once", action="store_true",
                        help="render one frame and exit (CI logs)")
    args = parser.parse_args(argv)
    try:
        if args.once:
            print(render_table(fetch_usage(args.url),
                               kv=fetch_kv(args.url),
                               picks=fetch_picks(args.url),
                               capacity=fetch_capacity(args.url)))
            return 0
        while True:
            frame = render_table(fetch_usage(args.url), color=True,
                                 kv=fetch_kv(args.url),
                                 picks=fetch_picks(args.url),
                                 capacity=fetch_capacity(args.url))
            sys.stdout.write(CLEAR + frame + "\n"
                             + f"{DIM}{args.url}  ^C to quit{RESET}\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    except OSError as e:
        print(f"lig-top: cannot reach {args.url}/debug/usage: {e}",
              file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
