# Developer entrypoints (kubebuilder-style targets, reference Makefile parity).

IMG ?= gcr.io/PROJECT/tpu-inference-gateway:latest

.PHONY: test test-e2e chaos native native-asan native-tsan bench bench-check loadgen sim sim-check metrics-docs top usage-check lint typecheck docker-build install deploy undeploy fmt

test:            ## unit + integration tests (CPU, virtual 8-device mesh)
	python -m pytest tests/ -q -m "not e2e"

lint:            ## mechanical layer (ruff, when installed) + the repo-invariant linter (incl. the concurrency rules; --timings shows which rule is slow)
	@if command -v ruff >/dev/null 2>&1; then ruff check .; \
	else echo "ruff not installed — mechanical layer served by the invariant linter's mech-* fallback rules"; fi
	python -m llm_instance_gateway_tpu.lint --timings

typecheck:       ## scoped mypy gate over the contract-bearing core (mypy.ini)
	@if command -v mypy >/dev/null 2>&1; then mypy --config-file mypy.ini; \
	else echo "mypy not installed — SKIPPING the scoped typecheck gate (loud skip, not a pass)"; fi

native-asan:     ## sanitized native build: ASan/UBSan libligsched + hostile-snapshot FFI fuzz + ctypes parity
	python tools/native_asan_check.py

native-tsan:     ## thread-sanitized native build: concurrent pick_many vs snapshot swaps under the _call_lock protocol + lock-free const picks
	python tools/native_tsan_check.py

test-e2e:        ## full local stack: server + gateway + sidecar as processes
	python -m pytest tests/test_e2e_local.py -q -m e2e

chaos:           ## seeded fault-injection scenarios vs the in-process stack
	python tools/chaos.py --seed 0 --scenario all

native:          ## build the C++ scheduler hot path
	$(MAKE) -C llm_instance_gateway_tpu/native

bench:           ## north-star benchmark (one JSON line; runs on the TPU)
	python bench.py

bench-check:     ## CPU-deterministic microbench gate vs BASELINE_BENCH.json (>20% regression fails)
	env JAX_PLATFORMS=cpu python tools/bench_check.py

loadgen:         ## gateway load rig (200 fake pods x 5 adapters)
	python -m llm_instance_gateway_tpu.gateway.loadgen --requests 10000

sim:             ## routing-policy simulation sweep
	python -m llm_instance_gateway_tpu.sim.run --qps 20 30 --policies random production

sim-check:       ## deterministic twin-calibration scenario: observable recovery + committed TWIN_CALIBRATION.json reproduction + knee sanity
	env JAX_PLATFORMS=cpu python -m llm_instance_gateway_tpu.sim.run --twin-scenario

metrics-docs:    ## regenerate docs/METRICS.md from the metric registry
	python tools/gen_metrics_docs.py docs/METRICS.md

top:             ## one-shot lig-top render of a running gateway's /debug/usage
	python tools/lig_top.py --once --url $${LIG_URL:-http://localhost:8081}

usage-check:     ## invariant lint + typecheck + sanitized native builds + attribution conservation + noisy-neighbor + fairness + placement + multipool enforcement + statebus + fleet obs + profiler + decode levers + concurrency harness + KV economy + capacity twin + docs currency
	$(MAKE) lint
	$(MAKE) typecheck
	$(MAKE) native-asan
	$(MAKE) native-tsan
	$(MAKE) sim-check
	python -m pytest tests/test_usage.py tests/test_fairness.py tests/test_placement.py tests/test_multipool.py tests/test_statebus.py tests/test_fleetobs.py tests/test_profiler.py tests/test_decode_levers.py tests/test_kv_ledger.py tests/test_kvobs.py tests/test_capacity.py tests/test_sim.py tests/test_metrics_docs.py tests/test_lint.py tests/test_concurrency.py -q
	python tools/chaos.py --seed 0 --scenario noisy_neighbor
	python tools/chaos.py --seed 0 --scenario adapter_flood
	python tools/chaos.py --seed 0 --scenario cold_start_storm
	python tools/chaos.py --seed 0 --scenario replica_partition
	python tools/chaos.py --seed 0 --scenario saturation_ramp

docker-build:    ## build the framework image
	docker build -t $(IMG) .

install:         ## install CRDs
	kubectl apply -f deploy/crds/

deploy: install  ## deploy gateway + model-server pool
	kubectl apply -f deploy/gateway/ -f deploy/model-server/

undeploy:
	kubectl delete -f deploy/gateway/ -f deploy/model-server/ --ignore-not-found
